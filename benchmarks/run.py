#!/usr/bin/env python
"""Benchmark + profiling harness over the five BASELINE presets (SURVEY.md
section 5 "Tracing/profiling": the events/sec bench harness is a first-class
deliverable; the reference has no perf tooling at all).

Usage:
    python benchmarks/run.py                      # all configs, full scale
    python benchmarks/run.py --configs 1 2 --quick
    python benchmarks/run.py --configs 3 --profile /tmp/trace
    python benchmarks/run.py --out results.json

Per config: build the preset, one warm-up run (compilation), then a timed
run with ``jax.block_until_ready``; optional ``jax.profiler.trace`` around
the timed region (view with TensorBoard/XProf). Writes one JSON object per
config; ``vs_baseline`` is the events/sec speedup over the NumPy oracle on
a scaled-down component of the same shape (the oracle's per-event cost is
O(sources), so full-size oracle runs are infeasible by construction — that
gap IS the point of the rebuild).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# (scale, end_time, extra kwargs, oracle feed-count sample) per config
# ``n_seeds`` (popped before build_preset) sizes the seed sweep: the
# reference's unit of work is the Monte-Carlo sweep over seeds (SURVEY.md
# section 3.5 "for seed in seeds ..."), so the small single-component
# configs (1, 5) bench a 1024-seed sweep — one vmap batch at the measured
# cache-optimal CPU lane count (benchmarks/scaling_r05_cpu.json peaks at
# B=1000-2500), where compute dominates dispatch (round-4 verdict weak-2:
# the old 64-lane sweep spent most of its wall on per-dispatch overhead
# and read as 4x the oracle; the oracle denominator is per-component and
# unaffected by the sweep width).
_FULL = {
    1: dict(scale=1.0, end_time=100.0, n_seeds=1024),
    2: dict(scale=1.0, end_time=100.0, wall_cap=1024, post_cap=8192),
    3: dict(scale=1.0, end_time=100.0),
    # q scales the posting cost with the follower count: at q=1 RedQueen
    # against 100k unit-rate feeds posts ~100*sqrt(1e5) ~ 31.6k times (no
    # real broadcaster's budget); q=2500 gives ~630 posts over the horizon,
    # the paper's few-posts-per-unit-time regime, and keeps the post buffer
    # (and the [F, post_cap] metric blocks) sane.
    4: dict(scale=1.0, end_time=100.0, q=2500.0, post_cap=4096),
    5: dict(scale=1.0, end_time=100.0, n_seeds=1024),
}
_QUICK = {
    1: dict(scale=1.0, end_time=30.0, capacity=512),
    2: dict(scale=0.05, end_time=30.0, wall_cap=512, post_cap=1024),
    3: dict(scale=0.05, end_time=30.0, capacity=512),
    4: dict(scale=0.002, end_time=30.0, post_cap=1024),
    5: dict(scale=1.0, end_time=30.0, train_steps=30, capacity=512),
}
_DESC = {
    1: "toy: 1 Opt vs 10 Poisson feeds",
    2: "1 Opt vs 1k Hawkes feeds (star path)",
    3: "1k-broadcaster bipartite batch",
    4: "replay walls, 100k feeds (star path)",
    5: "RMTPP neural policy vs Poisson feeds",
}


def _time_preset(which, kw, seeds, profile_dir=None, reps: int = 3):
    import jax

    from redqueen_tpu.presets import build_preset, run_preset

    bundle = build_preset(which, **kw)
    run_preset(bundle, seeds)  # warm-up: compiles every kernel involved
    if profile_dir:
        ctx = jax.profiler.trace(profile_dir)
    else:
        import contextlib

        ctx = contextlib.nullcontext()
    # Best-of-reps (identical work each rep — same seeds): the stable
    # estimator on a 1-core box with 10-60% load noise; matches bench.py's
    # TIMED_REPS protocol. Profiled runs do a single rep (a trace of 3
    # identical repetitions is just 3x the file).
    secs = float("inf")
    for _ in range(1 if profile_dir else reps):
        # run_preset returns a dict of HOST floats/arrays (np.asarray on
        # every metric inside), so the dispatch is fully drained before
        # it returns — there is no async tail left to block on.
        t0 = time.perf_counter()  # rqlint: disable=RQ601
        with ctx:
            out = run_preset(bundle, seeds)
        secs = min(secs, time.perf_counter() - t0)
    return bundle, out, secs


def _oracle_events_per_sec(which, kw, n_feeds_cap=1000, T_cap=20.0):
    """NumPy-oracle events/sec on a SAME-SHAPE component at a reduced
    horizon.

    events/sec is a rate, so shrinking the horizon (not the shape) keeps
    the comparison honest: the oracle's per-event cost is O(sources), and
    the round-4 F=40 sample under-charged the big-F configs ~25x for the
    work the engine actually does at F=1000 (verdict weak-2 — the
    scoreboard read as 4x because the denominator was flattered, not
    because the engine was slow). Config 4's true F=100k would put a
    single oracle event at ~100k-element argmins — a same-RATE 1000-feed
    replay component is the largest same-kind shape that keeps the
    denominator measurable; the remaining 100x shape gap goes UNCHARGED
    (conservative: it can only understate vs_baseline)."""
    from redqueen_tpu.oracle.numpy_ref import SimOpts

    if which in (1, 3, 5):
        F, end_time = 10, min(float(kw.get("end_time", 100.0)), T_cap)
        others = [
            ("poisson", dict(src_id=100 + i, seed=50_000 + i, rate=1.0,
                             sink_ids=[i]))
            for i in range(10)
        ]
    elif which == 2:
        # Full config-2 shape (1000 Hawkes feeds); horizon cut so the
        # O(F)-per-event loop finishes in seconds.
        F, end_time = n_feeds_cap, min(float(kw.get("end_time", 100.0)), 10.0)
        others = [
            ("hawkes", dict(src_id=100 + i, seed=50_000 + i, l_0=0.5,
                            alpha=0.8, beta=2.0, sink_ids=[i]))
            for i in range(F)
        ]
    else:  # 4: replay walls at the same per-feed event rate
        from redqueen_tpu.data import synthetic_twitter

        F, end_time = n_feeds_cap, min(float(kw.get("end_time", 100.0)), 10.0)
        traces = synthetic_twitter(7, F, end_time)
        others = [
            ("realdata", dict(src_id=100 + i, times=traces[i], sink_ids=[i]))
            for i in range(F)
        ]
    so = SimOpts(src_id=0, sink_ids=list(range(F)), other_sources=others,
                 end_time=end_time, q=float(kw.get("q", 1.0)))

    if which == 5:
        # Same-KIND controlled policy: the engine runs the NEURAL RMTPP
        # broadcaster, so the denominator must pay the per-event GRU too
        # (oracle.numpy_ref.RMTPP, the NumPy twin) — an Opt denominator
        # under-charges the oracle for config 5's actual work. Untrained
        # weights: per-event COST is weight-independent.
        import jax
        from jax import random as jr

        from redqueen_tpu.models import rmtpp as _rmtpp

        hidden = int(kw.get("hidden", 8))
        w = jax.tree.map(
            lambda x: np.asarray(x, np.float64),
            _rmtpp.init_weights(jr.PRNGKey(0), hidden=hidden),
        )
        make = lambda seed: so.create_manager_with_rmtpp(  # noqa: E731
            seed=seed, weights=w, hidden=hidden)
    else:
        make = so.create_manager_with_opt

    # Pure-NumPy oracle loop: nothing dispatched, nothing to block on.
    t0 = time.perf_counter()  # rqlint: disable=RQ601
    events = 0
    for seed in range(2):
        mgr = make(seed)
        mgr.run_till()
        events += len(mgr.state.events)
    secs = time.perf_counter() - t0
    return events / max(secs, 1e-9)


def _config4_corpus_pipeline(kw, log):
    """Ingestion half of config 4 (round-4 verdict item 8): the synthetic
    corpus is written to a cached CSV ONCE, then every bench run re-ingests
    it through ``data.traces.load_csv(engine="auto")`` — the native C++
    parser — so ingestion → replay → metrics is one measured pipeline and
    the artifact records the corpus size and loader engine actually used."""
    import os

    from redqueen_tpu.data import synthetic_twitter, traces as traces_mod
    from redqueen_tpu.native import loader as native_loader

    end_time = float(kw.get("end_time", 100.0))
    scale = float(kw.get("scale", 1.0))
    n_users = max(int(round(100_000 * scale)), 1)
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "corpus_cache")
    os.makedirs(cache, exist_ok=True)
    # The cache key must cover EVERY generation parameter (a mean_rate
    # override reusing a stale 1.0-rate corpus would silently bench the
    # wrong workload); seed/max_len are constants below but keyed anyway.
    mean_rate = float(kw.get("mean_rate", 1.0))
    path = os.path.join(
        cache,
        f"config4_s{scale:g}_T{end_time:g}_r{mean_rate:g}_seed7_len256.csv",
    )
    if not os.path.exists(path):
        log(f"config 4: generating corpus ({n_users} users) -> {path}")
        tr = synthetic_twitter(7, n_users, end_time,
                               mean_rate=float(kw.get("mean_rate", 1.0)),
                               max_len=256)
        traces_mod.save_csv(path, tr)
    engine = "native" if native_loader.available() else "python"
    # Host-side CSV ingestion (C++/python parser) — no device dispatch.
    t0 = time.perf_counter()  # rqlint: disable=RQ601
    tr = traces_mod.load_csv(path, engine="auto")
    load_secs = time.perf_counter() - t0
    rows = int(sum(len(t) for t in tr))
    log(f"config 4: ingested {rows} rows / {len(tr)} users in "
        f"{load_secs:.2f}s via the {engine} loader "
        f"({rows / max(load_secs, 1e-9):,.0f} rows/s)")
    meta = {
        "corpus_rows": rows,
        "corpus_users": len(tr),
        "corpus_csv_bytes": os.path.getsize(path),
        "loader_engine": engine,
        "ingest_secs": round(load_secs, 3),
        "ingest_rows_per_sec": round(rows / max(load_secs, 1e-9), 1),
    }
    return tr, meta


def bench_config(which: int, quick: bool = False, profile_dir=None,
                 n_seeds=None, log=log):
    kw = dict((_QUICK if quick else _FULL)[which])
    # The preset table's n_seeds is a DEFAULT; an explicit caller/--seeds
    # value always wins (n_seeds=None means "not explicitly requested").
    preset_seeds = kw.pop("n_seeds", 4)
    if n_seeds is None:
        n_seeds = preset_seeds
    seeds = 0 if which == 3 else np.arange(n_seeds)
    meta = {}
    if which == 4 and not quick:
        kw["traces"], meta = _config4_corpus_pipeline(kw, log)
    bundle, out, secs = _time_preset(which, kw, seeds, profile_dir)
    events = out["events"]
    eps = events / max(secs, 1e-9)
    kw.pop("traces", None)  # the oracle sample generates its own
    o_eps = _oracle_events_per_sec(which, kw)
    log(f"config {which} ({_DESC[which]}): {events} events in {secs:.3f}s "
        f"-> {eps:,.0f} events/s; top-{1} {out['mean_time_in_top_k']:.2f}/"
        f"{out['end_time']}, posts {out['mean_posts']:.1f}; "
        f"oracle {o_eps:,.0f} ev/s (same-shape sample) -> {eps / o_eps:,.1f}x")
    res = {
        "metric": f"config{which} events/sec ({_DESC[which]})",
        "value": round(eps, 1),
        "unit": "events/s",
        "vs_baseline": round(eps / o_eps, 2),
    }
    res.update(meta)
    return res


def bench_learn(quick: bool = False, out_path: str = None, log=log):
    """``--learn``: the Hawkes-estimation micro-bench (CPU), two phases.

    1. **Recover** — simulate a known 3-dim world with the repo kernel,
       fit with BOTH solvers (``redqueen_tpu.learn``): iterations to
       converge, wall-clock, and parameter-recovery error are committed
       numbers, not assumptions.
    2. **Corpus scale** — the config-4 corpus (8.58M rows / 100k users at
       full scale) re-ingested through the native C++ loader, hash-
       grouped into fit dimensions (``learn.ingest.from_traces``), and
       EM-fitted: events/s fitted and PER-ITERATION wall-clock, where
       ``iter1 ≈ iter2`` is the measured no-recompilation-churn claim
       (one compiled kernel per padded shape; rqlint RQ801 guards the
       code path statically).

    The artifact is the enveloped ``rq.learn.bench/1`` (default
    ``LEARN_BENCH.json``).
    """
    import numpy as np

    from redqueen_tpu import GraphBuilder, simulate
    from redqueen_tpu.learn import fit_hawkes, ingest
    from redqueen_tpu.runtime import integrity

    # ---- phase 1: simulate -> fit -> recover ----
    D = 3
    mu_t = np.array([0.3, 0.5, 0.4])
    a_t = np.array([0.8, 0.5, 0.6])
    b_t = np.array([2.0, 1.5, 2.5])
    T = 200.0 if quick else 600.0
    gb = GraphBuilder(n_sinks=D, end_time=T)
    rows = gb.add_hawkes(mu_t, a_t, b_t)
    cfg, params, adj = gb.build(capacity=4096)
    stream = ingest.from_event_log(simulate(cfg, params, adj, seed=7),
                                   sources=rows)
    recover = {"n_events": stream.n_events, "dims": D, "T": T}
    fw_warmup = 30  # explicit so the sweep accounting below stays honest
    for solver, iters in (("em", 150), ("fw", 300)):
        # Warm-up fit compiles every kernel involved (same protocol as
        # _time_preset): the committed secs/events_per_sec measure
        # FITTING, not one-time jit compilation.
        fit_hawkes(stream, solver=solver, max_iters=2, fw_beta_warmup=2)
        # fit_hawkes returns host scalars/arrays (its device_gets are
        # the sync); nothing asynchronous is left when it returns.
        t0 = time.perf_counter()  # rqlint: disable=RQ601
        f = fit_hawkes(stream, solver=solver, max_iters=iters, tol=1e-7,
                       fw_beta_warmup=fw_warmup)
        secs = time.perf_counter() - t0
        # The FW wall includes its EM decay warm-up sweeps: count them
        # in the throughput numerator too (same units as the wall).
        sweeps = f.n_iter + (fw_warmup if solver == "fw" else 0)
        br_err = float(np.max(np.abs(
            np.diag(f.branching()) - a_t / b_t)))
        recover[solver] = {
            "iters": f.n_iter, "converged": f.converged,
            "secs": round(secs, 3),
            "warmup_sweeps_included": sweeps - f.n_iter,
            "events_per_sec": round(stream.n_events * sweeps
                                    / max(secs, 1e-9), 1),
            "branching_abs_err": round(br_err, 4),
            "final_loglik": round(f.final_loglik, 2),
        }
        log(f"learn recover [{solver}]: {f.n_iter} iters in {secs:.2f}s "
            f"(converged={f.converged}), branching err {br_err:.3f}")

    # ---- phase 2: corpus-scale fit via the native loader ----
    kw = dict(_QUICK[4] if quick else _FULL[4])
    traces, corpus_meta = _config4_corpus_pipeline(kw, log)
    n_dims = 16 if quick else 64
    c_stream = ingest.from_traces(traces, n_dims=n_dims, assign="hash",
                                  t_end=float(kw.get("end_time", 100.0)))
    chunks = ingest.chunk_events(c_stream)
    # Three timed calls through the SAME compiled kernel: cold (compile +
    # 1 iter), warm 1 iter, warm 3 iters.  The warm pair isolates the
    # marginal per-iteration cost ((warm3 - warm1) / 2 — the constant
    # final-scoring pass cancels), and warm3 staying ~3x warm1's
    # iteration share IS the measured no-recompilation-churn claim.
    walls = []
    for iters in (1, 1, 3):
        # fit_hawkes fully drains its dispatches before returning (the
        # trajectory device_get is the sync).
        t0 = time.perf_counter()  # rqlint: disable=RQ601
        fit_hawkes(chunks, solver="em", max_iters=iters, tol=0.0)
        walls.append(round(time.perf_counter() - t0, 3))
    per_iter = max((walls[2] - walls[1]) / 2, 1e-9)
    corpus = {
        **corpus_meta,
        "n_dims": n_dims,
        "events_fitted": c_stream.n_events,
        "chunk_shape": list(chunks.dt.shape),
        "wall_secs_cold_1iter": walls[0],
        "wall_secs_warm_1iter": walls[1],
        "wall_secs_warm_3iter": walls[2],
        "em_secs_per_iter": round(per_iter, 3),
        "events_per_sec_fitted": round(
            c_stream.n_events / max(per_iter, 1e-9), 1),
        "compile_overhead_secs": round(walls[0] - walls[1], 3),
    }
    log(f"learn corpus: {c_stream.n_events} events x {n_dims} dims -> "
        f"{corpus['events_per_sec_fitted']:,.0f} events/s fitted "
        f"({per_iter:.2f}s/iter; cold/warm1/warm3 walls {walls}; "
        f"compile overhead {corpus['compile_overhead_secs']:.2f}s)")

    payload = {"recover": recover, "corpus": corpus, "quick": quick}
    if out_path:
        integrity.write_json(out_path, payload, schema="rq.learn.bench/1")
    return {
        "metric": f"learn EM events/sec fitted (config-4 corpus, "
                  f"{n_dims} dims)",
        "value": corpus["events_per_sec_fitted"],
        "unit": "events/s",
        "vs_baseline": None,
        **payload,
    }


# A fresh runtime's first batches pay one-time costs the steady state
# never sees again: the jitted apply compiles on the process's first
# instance (~450ms on this CPU), and every NEW instance pays smaller
# per-instance lazy-init costs on its first few applies (50-200ms vs
# ~5ms steady; measured — a module-level warm-up runtime does NOT absorb
# them, which is exactly the 104ms `max_ms` outlier in the r06
# SERVING_BENCH.json).  So the warm-up drives the MEASURED runtime
# itself, then `reset_metrics()` starts the steady-state ledger: the
# committed latency percentiles (incl. `max_ms`) describe serving, not
# one-time initialization.
SERVING_WARMUP_BATCHES = 8


# The wire-speed ingest-path knobs (ROADMAP item 2): batches per jitted
# dispatch / journal record, and the async-group-commit durability
# window.  Committed IN the artifact (``durability``) so no throughput
# number is ever quoted without its durability cost.
SERVING_COALESCE = 32
SERVING_MAX_UNFLUSHED = 64
SERVING_FLUSH_DELAY_MS = 25.0

# The PR 16 measured configuration — the QUORUM durability tier: the
# binary fixed-slot journal (one compact serialize + crc32 + mmap copy
# per record, no sha256 envelope on the hot path) replicated to
# SERVING_REPLICATION_FACTOR in-process followers with a
# SERVING_REPLICATION_QUORUM in-memory ack point, fsync demoted to the
# lagging background checkpoint.  quorum=1 with factor=2 means every
# ack is held by leader + >=1 follower — any single-node SIGKILL is
# survived outright — while one slow follower cannot stall the ack.
# The sync- and window-tier comparisons ride along in the artifact so
# the tier's cost/guarantee trade is measured, never implied.
SERVING_JOURNAL_FORMAT = "binary"
SERVING_REPLICATION_FACTOR = 2
SERVING_REPLICATION_QUORUM = 1

# The clustered phase of the serving micro-bench: steady-state
# throughput at N socket-placed worker processes, every shard journal
# on the same quorum tier — committed beside the single-runtime number
# so the durability upgrade is priced at the placement the ROADMAP
# quotes (PR 11: 198,981 ev/s at N=4 sockets, pre-quorum).
SERVING_CLUSTER_SHARDS = 4

# Interleaved repetitions of the cluster phase (PR 11 config vs quorum
# tier, best-of each): one socket-cluster pass is ~15-20s, long enough
# that single-pass A/B is dominated by scheduler drift on a small box.
SERVING_CLUSTER_REPS = 3

# Whole serve-rounds exported into the committed SERVING_TRACE.json
# (round-aligned so coverage/critical-path stay well-defined; the full
# traced run still feeds the artifact's stage_breakdown block — the
# subset bounds the committed file, the summary covers everything).
TRACE_EXPORT_ROUNDS = 16


def _round_chunks(batches, size):
    for i in range(0, len(batches), size):
        yield batches[i:i + size]


def bench_serving(quick: bool = False, out_path: str = None,
                  trace_out_path: str = None, log=log):
    """Steady-state serving micro-bench (CPU, small graph): drive a
    deterministic synthetic ingest stream through a journaled
    ``ServingRuntime`` on the WIRE-SPEED path — coalesced applies (one
    jitted dispatch + one journal record per round) on the QUORUM
    durability tier (binary fixed-slot journal, replicated group
    commit: the ack point is in-memory receipt by a follower quorum,
    fsync a lagging background checkpoint) — and report sustained
    events/s + decision latency (raw, trimmed, and windowed
    percentiles).  The artifact is the same enveloped
    ``rq.serving.metrics/1`` schema the runtime itself emits,
    durability tier included; a same-workload ``tier_comparison``
    (``sync``: fsync-before-ack, the PR 6 contract; ``window``: async
    group commit, the PR 13 bounded-loss tier) rides along so the
    cost/guarantee trade of the headline is measured, never implied,
    and a ``cluster`` block prices the same tier at
    :data:`SERVING_CLUSTER_SHARDS` socket-placed worker processes.

    Journaling is IN the measured path on purpose; snapshots are off
    (cadence-driven, not throughput-relevant).  The first
    :data:`SERVING_WARMUP_BATCHES` batches warm the measured runtime
    and are excluded from the artifact (see the constant's comment for
    why a separate warm-up runtime is not enough).

    **Telemetry:** the committed throughput is measured UNTRACED, then
    the identical workload re-runs with ``runtime.telemetry`` enabled —
    every round under one root span, the serving span chain (admit →
    coalesce → dispatch → sync → journal append → fsync → ack) beneath
    it.  Three things land beside the throughput number: the
    ``stage_breakdown`` (full traced run, ``telemetry.summarize`` —
    the same aggregation ``tools/rqtrace.py`` renders), the ``tracing``
    overhead comparison (traced events/s vs untraced, the <= 5%
    contract the CI smoke enforces), and the enveloped
    ``rq.telemetry.trace/1`` artifact (``trace_out_path``, default
    ``SERVING_TRACE.json`` — round-aligned span subset, flagged when
    truncated).
    """
    import tempfile

    from redqueen_tpu import serving
    from redqueen_tpu.runtime import integrity as _integrity
    from redqueen_tpu.runtime import telemetry as _telemetry

    n_feeds = 256 if quick else 2048
    n_batches = 256 if quick else 2048
    epb = 16 if quick else 64
    warm = SERVING_WARMUP_BATCHES
    batches = serving.synthetic_stream(0, n_batches + warm, n_feeds,
                                       events_per_batch=epb)
    mbe = 4 * epb
    tel = _telemetry.get()

    def run(flush_mode, traced=False, fmt=None, repl=0):
        tmpdir = tempfile.mkdtemp(prefix="rq-serving-bench-")
        tel.configure(enabled=traced, reset=True)
        try:
            rt = serving.ServingRuntime(
                n_feeds=n_feeds, dir=tmpdir, snapshot_every=10 ** 9,
                queue_capacity=2 * SERVING_COALESCE, reorder_window=8,
                max_batch_events=mbe, coalesce=SERVING_COALESCE,
                flush_mode=flush_mode,
                max_unflushed_records=SERVING_MAX_UNFLUSHED,
                max_flush_delay_ms=SERVING_FLUSH_DELAY_MS,
                journal_format=fmt,
                replication_factor=repl,
                replication_quorum=(SERVING_REPLICATION_QUORUM
                                    if repl else None))
            with rt:
                for b in batches[:warm]:
                    rt.submit(b)
                    rt.poll()
                rt.reset_metrics()  # steady state starts here
                tel.configure(reset=True)  # warm-up spans excluded too
                # One poll round per coalesce-width chunk: the round IS
                # the dispatch/journal unit the wire-speed path
                # amortizes over.  The root span per round is a no-op
                # singleton when tracing is off (the zero-cost
                # contract), so traced and untraced runs share this
                # exact loop.
                for chunk in _round_chunks(batches[warm:],
                                           SERVING_COALESCE):
                    with tel.trace("serve.round"):
                        for b in chunk:
                            rt.submit(b)
                        rt.poll()
                # Report only — the artifact lands exactly ONCE at the
                # end, from the BEST group rep plus the breakdown/
                # tracing blocks (per-rep writes would land a non-best,
                # breakdown-less payload three times for nothing).
                health = rt.gather()[1]
                return rt.metrics.report(
                    pending=rt.pending,
                    extra={"n_feeds": rt.n_feeds, "q": rt.q,
                           "applied_seq": rt.applied_seq,
                           "durability": rt.durability(),
                           "health_sick_edges": int(
                               (health != 0).sum())})
        finally:
            import shutil

            tel.configure(enabled=False)
            # the journal scratch dir has no value past the report —
            # don't leave thousands of records in /tmp per invocation
            shutil.rmtree(tmpdir, ignore_errors=True)

    sync_rep = run("sync")
    # The PR 13 committed tier (async group commit, JSONL, no
    # replication) — the window the quorum tier retires, measured on
    # the same workload so the upgrade is a number, not a claim.
    window_rep = run("group")
    # INTERLEAVED pairs (the telemetry_overhead.py methodology): this
    # sandbox's IO-stall waves move a single run by ~10%, far above the
    # ~1-3% true tracing overhead being compared (measured: 8-pair
    # median 1.15%, best-of even negative) — sequential best-of runs
    # let one wave eat a whole mode's reps, so the modes alternate.
    # The best TRACED run's spans feed the breakdown + artifact; same
    # workload, same durability window throughout.  The MEASURED
    # configuration is the quorum tier: binary journal + replicated
    # group commit (fsync off the ack path entirely).
    payload = None
    traced_rep, trace_payload = None, None
    off_all, on_all = [], []
    for _ in range(7):
        rep = run("group", fmt=SERVING_JOURNAL_FORMAT,
                  repl=SERVING_REPLICATION_FACTOR)
        off_all.append(float(rep["events_per_sec"]))
        if payload is None or rep["events_per_sec"] > \
                payload["events_per_sec"]:
            payload = rep
        trep = run("group", traced=True, fmt=SERVING_JOURNAL_FORMAT,
                   repl=SERVING_REPLICATION_FACTOR)
        # Whole payload per rep (spans AND the counters/histograms the
        # same rep recorded — run() resets telemetry at entry), so the
        # exported artifact is internally consistent: its counters
        # describe the same rep its spans do.
        pay_i = tel.payload()
        tel.configure(reset=True)
        on_all.append(float(trep["events_per_sec"]))
        if traced_rep is None or trep["events_per_sec"] > \
                traced_rep["events_per_sec"]:
            traced_rep, trace_payload = trep, pay_i
    trace_spans = trace_payload["spans"]
    breakdown = _telemetry.summarize(trace_spans)

    def _median(xs):
        s = sorted(xs)
        return s[len(s) // 2]

    # The overhead estimate compares MEDIANS of the interleaved runs —
    # max-of-N is itself a noisy statistic under ~10% IO waves (a lucky
    # untraced max against an unlucky traced max reads as phantom
    # overhead), while the median difference converges on the real
    # ~3% span cost.  The headline throughput stays best-of (the bench
    # discipline for the NUMBER); both views are committed.
    off_eps = float(payload["events_per_sec"])
    on_eps = float(traced_rep["events_per_sec"])
    off_med, on_med = _median(off_all), _median(on_all)
    overhead_pct = (round(100.0 * (off_med - on_med) / off_med, 2)
                    if off_med > 0 else None)
    trace_path = trace_out_path or os.path.join(
        os.path.dirname(out_path or "SERVING_BENCH.json") or ".",
        "SERVING_TRACE.json")
    # Round-aligned span subset: whole traces only (coverage and the
    # critical path stay well-defined), size bounded, truncation
    # flagged — never a silently partial round.
    root_tids = [s["tid"] for s in trace_spans if "parent" not in s]
    keep = set(root_tids[:TRACE_EXPORT_ROUNDS])
    sub = [s for s in trace_spans if s["tid"] in keep]
    trace_payload.update({
        "spans": sub, "n_spans": len(sub),
        "rounds_total": len(root_tids),
        "rounds_exported": min(TRACE_EXPORT_ROUNDS, len(root_tids)),
        "spans_truncated": len(sub) < len(trace_spans),
        "workload": {"n_feeds": n_feeds, "n_batches": n_batches,
                     "events_per_batch": epb,
                     "coalesce": SERVING_COALESCE},
        "stage_breakdown": breakdown,
        "events_per_sec_traced": on_eps,
        "events_per_sec_untraced": off_eps,
        "durability": traced_rep["durability"],
    })
    _integrity.write_json(trace_path, trace_payload,
                          schema=_telemetry.TRACE_SCHEMA)

    # ---- clustered wire-speed phase: the SAME quorum tier at
    # SERVING_CLUSTER_SHARDS socket-placed worker processes (the PR 11
    # placement whose 198,981 ev/s headline the ROADMAP quotes), so
    # the durability upgrade is priced where it deploys.  Steady-state
    # only — the kill-one-shard chaos phase stays with
    # ``--serving --shards N`` (bench_serving_cluster).
    cluster = None
    if not quick:
        import shutil as _shutil

        def run_cluster(d, **kw):
            """One steady-state pass at SERVING_CLUSTER_SHARDS socket
            workers: warm, reset, serve, report."""
            with serving.ServingCluster(
                    n_feeds=n_feeds, n_shards=SERVING_CLUSTER_SHARDS,
                    dir=d, snapshot_every=10 ** 9,
                    queue_capacity=2 * SERVING_COALESCE,
                    reorder_window=8, max_batch_events=mbe,
                    coalesce=SERVING_COALESCE, flush_mode="group",
                    max_unflushed_records=SERVING_MAX_UNFLUSHED,
                    max_flush_delay_ms=SERVING_FLUSH_DELAY_MS,
                    placement="sockets", **kw) as cl:
                for b in batches[:warm]:
                    cl.submit(b)
                    cl.poll()
                cl.reset_metrics()
                for chunk in _round_chunks(batches[warm:],
                                           SERVING_COALESCE):
                    cl.submit_many(chunk)
                    cl.poll()
                rep = cl.metrics.report(cl.pending_by_shard,
                                        cl.health_by_shard)
                return {
                    "n_shards": SERVING_CLUSTER_SHARDS,
                    "placement": "sockets",
                    "events_per_sec": rep["events_per_sec"],
                    "batches_per_sec": rep["batches_per_sec"],
                    "decision_p50_ms":
                        rep["decision_latency"]["p50_ms"],
                    "decision_p99_ms":
                        rep["decision_latency"]["p99_ms"],
                    "reconciles": rep["reconciles"],
                    "durability": cl.durability(),
                }

        croot = tempfile.mkdtemp(prefix="rq-serving-bench-cluster-")
        try:
            # The PR 11 configuration (jsonl journal, window tier, no
            # replication) measured in the SAME run on the SAME box —
            # the like-for-like floor the quorum tier must not fall
            # under.  The committed PR 11 headline (198,981 ev/s) was
            # recorded on a multi-core host; socket workers time-slice
            # a single core here, so same-run baselining is the only
            # honest comparison.  Interleaved best-of-N, same trick as
            # the tracing-overhead phase: a whole-cluster pass is long
            # enough that scheduler/page-cache drift between two single
            # passes swamps the effect being measured.
            baseline, cluster = None, None
            for i in range(SERVING_CLUSTER_REPS):
                b = run_cluster(os.path.join(croot, f"pr11-{i}"))
                q = run_cluster(
                    os.path.join(croot, f"quorum-{i}"),
                    journal_format=SERVING_JOURNAL_FORMAT,
                    replication_factor=SERVING_REPLICATION_FACTOR,
                    replication_quorum=SERVING_REPLICATION_QUORUM)
                if (baseline is None or b["events_per_sec"]
                        > baseline["events_per_sec"]):
                    baseline = b
                if (cluster is None or q["events_per_sec"]
                        > cluster["events_per_sec"]):
                    cluster = q
            cluster["baseline_pr11_config"] = baseline
            cluster["reps"] = SERVING_CLUSTER_REPS
            cluster["vs_pr11_config"] = round(
                cluster["events_per_sec"]
                / max(baseline["events_per_sec"], 1e-9), 4)
            log(f"serving cluster [sockets, quorum tier]: "
                f"{SERVING_CLUSTER_SHARDS} shards -> "
                f"{cluster['events_per_sec']:,.0f} events/s steady "
                f"(decision p99 {cluster['decision_p99_ms']}ms; "
                f"{cluster['vs_pr11_config']:.2f}x the PR 11 config "
                f"at {baseline['events_per_sec']:,.0f} ev/s same-run)")
        finally:
            _shutil.rmtree(croot, ignore_errors=True)

    # Land the metrics artifact (the ONE write) WITH the breakdown +
    # overhead blocks beside its throughput number — no more
    # hand-reconstructed bottleneck analyses next to a bare events/s.
    from redqueen_tpu.serving.metrics import METRICS_SCHEMA

    payload["stage_breakdown"] = breakdown
    payload["cluster"] = cluster
    payload["tier_comparison"] = {
        "sync": {
            "events_per_sec": sync_rep["events_per_sec"],
            "decision_p99_ms":
                sync_rep["decision_latency"]["p99_ms"],
            "durability": sync_rep["durability"],
        },
        "window": {
            "events_per_sec": window_rep["events_per_sec"],
            "decision_p99_ms":
                window_rep["decision_latency"]["p99_ms"],
            "durability": window_rep["durability"],
        },
    }
    payload["tracing"] = {
        "events_per_sec_traced": on_eps,
        "events_per_sec_untraced": off_eps,
        "events_per_sec_traced_median": on_med,
        "events_per_sec_untraced_median": off_med,
        "interleaved_reps": len(off_all),
        "overhead_pct": overhead_pct,
        "within_5pct": (overhead_pct is not None
                        and overhead_pct <= 5.0),
        "coverage": breakdown["coverage"],
        "trace_artifact": trace_path,
    }
    _integrity.write_json(out_path or "SERVING_BENCH.json", payload,
                          schema=METRICS_SCHEMA)
    lat = payload["decision_latency"]
    log(f"serving [quorum tier: binary journal, "
        f"factor={SERVING_REPLICATION_FACTOR} "
        f"quorum={SERVING_REPLICATION_QUORUM}, "
        f"coalesce={SERVING_COALESCE}]: "
        f"{payload['events_applied']} events in "
        f"{payload['busy_s']:.3f}s -> {payload['events_per_sec']:,.0f} "
        f"events/s sustained ({payload['applied']} micro-batches, "
        f"journaled, {warm} warm-up batches excluded); decision "
        f"p50 {lat['p50_ms']}ms p99 {lat['p99_ms']}ms "
        f"(trimmed {lat['p99_trimmed_ms']}ms, windowed "
        f"{lat['p99_window_median_ms']}ms) max {lat['max_ms']}ms; "
        f"tier comparison: sync {sync_rep['events_per_sec']:,.0f} / "
        f"window {window_rep['events_per_sec']:,.0f} ev/s")
    log(f"serving telemetry: traced median {on_med:,.0f} ev/s vs "
        f"untraced median {off_med:,.0f} ev/s (overhead "
        f"{overhead_pct}%; bests {on_eps:,.0f} / {off_eps:,.0f}); "
        f"stage coverage {breakdown['coverage']}; "
        f"trace -> {trace_path}")
    return {
        "metric": f"serving events/sec ({n_feeds} feeds, journaled "
                  f"quorum-replicated group-commit "
                  f"(binary, factor={SERVING_REPLICATION_FACTOR}), "
                  f"coalesce={SERVING_COALESCE}, ~{epb} ev/batch)",
        "value": payload["events_per_sec"],
        "unit": "events/s",
        "vs_baseline": None,
        "decision_p50_ms": lat["p50_ms"],
        "decision_p99_ms": lat["p99_ms"],
        "decision_p99_trimmed_ms": lat["p99_trimmed_ms"],
        "decision_p99_window_median_ms": lat["p99_window_median_ms"],
        "decision_max_ms": lat["max_ms"],
        "warmup_batches_excluded": warm,
        "batches_per_sec": payload["batches_per_sec"],
        "durability": payload["durability"],
        "tier_comparison": payload["tier_comparison"],
        "cluster": cluster,
        "tracing": payload["tracing"],
        "stage_breakdown": breakdown,
        "reconciles": payload["reconciles"],
    }


def bench_serving_cluster(n_shards: int, quick: bool = False,
                          out_path: str = None,
                          placement: str = "in-process", log=log):  # noqa: C901
    """``--serving --shards N [--workers]``: the sharded-cluster
    serving bench.

    Three phases, all with the same warm-up exclusion as
    :func:`bench_serving`:

    1. **Scaling sweep** — steady-state events/s and decision latency at
       1, 2, 4, ... up to ``n_shards`` fault domains (same global
       stream, journal fsync per sub-batch in the measured path), so the
       per-shard fault-isolation overhead is a committed number, not a
       guess.  ``--workers`` runs the sweep with every shard in its own
       subprocess: the N fsyncs/applies run in true parallel instead of
       serializing behind one GIL — the placement's throughput claim.
    2. **Placement comparison** (worker placement only) — the SAME
       workload at ``n_shards`` in process, committed next to the
       worker number so "worker mode beats in-process at equal shard
       count" is measured in one artifact, never asserted.
    3. **Kill-one-shard chaos** — at ``n_shards``, kill fault domain 0
       mid-stream (``auto_recover`` off so the outage window is
       driver-controlled; under ``--workers`` this is a REAL SIGKILL of
       a live worker process), keep serving the second half of the
       stream on the surviving shards (measuring their throughput
       during the outage), then recover the dead shard in place
       (snapshot + digest-asserted journal replay — the MTTR number;
       worker MTTR honestly includes the replacement process's spawn +
       jax import + first compile) and retransmit until the cluster
       reconverges.  The artifact is the chaos cluster's own
       ``rq.serving.metrics/2`` report — crashes, lost-on-crash and
       shed-unavailable seqs, recovery replay counts, and a closed
       accounting identity THROUGH the outage — with the sweep +
       comparison + MTTR numbers under ``"bench"``.
    """
    import os as _os
    import shutil
    import tempfile
    import time as _time

    from redqueen_tpu import serving
    from redqueen_tpu.runtime import integrity as _integrity

    n_feeds = 256 if quick else 2048
    n_batches = 128 if quick else 2048
    epb = 16 if quick else 64
    warm = SERVING_WARMUP_BATCHES
    mbe = 4 * epb
    batches = serving.synthetic_stream(0, n_batches + warm, n_feeds,
                                       events_per_batch=epb)
    round_size = SERVING_COALESCE

    # The before/after contract: capture the previous committed
    # headline (whatever durability/coalesce it ran under) before this
    # run overwrites the artifact.
    before = None
    prev_path = out_path or "SERVING_BENCH.json"
    if _os.path.exists(prev_path):
        try:
            prev = _integrity.read_json(prev_path, do_quarantine=False)
            prev_sweep = prev.get("bench", {}).get("sweep") or []
            before = {
                # whole-artifact-window rate (chaos phase included)...
                "events_per_sec": prev.get("events_per_sec"),
                # ...and the steady-state sweep headline at its top
                # shard count — the number the after/steady compares to.
                "steady_events_per_sec": (
                    prev_sweep[-1].get("events_per_sec")
                    if prev_sweep else None),
                "n_shards": prev.get("n_shards"),
                "decision_latency": prev.get("decision_latency"),
                "durability": prev.get(
                    "durability", {"flush_mode": "sync",
                                   "fsync_every_n": 1, "coalesce": 1}),
                "bench": {"placement": prev.get("bench", {}).get(
                    "placement")},
            }
        except Exception:  # noqa: BLE001 — a foreign/old artifact is
            before = None  # context, never a reason to fail the bench

    def make_cluster(k, d, placement=placement, **kw):
        return serving.ServingCluster(
            n_feeds=n_feeds, n_shards=k, dir=d, snapshot_every=10 ** 9,
            queue_capacity=2 * round_size, reorder_window=8,
            max_batch_events=mbe, coalesce=SERVING_COALESCE,
            flush_mode="group",
            max_unflushed_records=SERVING_MAX_UNFLUSHED,
            max_flush_delay_ms=SERVING_FLUSH_DELAY_MS,
            placement=placement, **kw)

    def serve_rounds(cl, stream):
        """One submit_many + poll round per coalesce-width chunk — the
        wire-speed ingest loop (one frame per round per shard, one
        jitted dispatch + one journal record per round per shard)."""
        for chunk in _round_chunks(stream, round_size):
            cl.submit_many(chunk)
            cl.poll()

    def run_steady(cl):
        """Warm the measured cluster, then serve the stream steady-state
        and return its metrics report."""
        for b in batches[:warm]:
            cl.submit(b)
            cl.poll()
        cl.reset_metrics()
        serve_rounds(cl, batches[warm:])
        return cl.metrics.report(cl.pending_by_shard,
                                 cl.health_by_shard)

    sweep_counts = [k for k in (1, 2, 4, 8, 16, 32) if k < n_shards]
    sweep_counts.append(n_shards)
    root = tempfile.mkdtemp(prefix="rq-serving-cluster-bench-")
    sweep = []
    in_process_comparison = None
    try:
        for k in sweep_counts:
            with make_cluster(k, _os.path.join(root, f"sweep-{k}")) as cl:
                rep = run_steady(cl)
            lat = rep["decision_latency"]
            sweep.append({
                "n_shards": k,
                "placement": placement,
                "events_per_sec": rep["events_per_sec"],
                "batches_per_sec": rep["batches_per_sec"],
                "decision_p50_ms": lat["p50_ms"],
                "decision_p99_ms": lat["p99_ms"],
                "decision_p99_trimmed_ms": lat["p99_trimmed_ms"],
                "decision_p99_window_median_ms":
                    lat["p99_window_median_ms"],
                "decision_max_ms": lat["max_ms"],
                "reconciles": rep["reconciles"],
            })
            log(f"serving sweep [{placement}]: {k} shard(s) -> "
                f"{rep['events_per_sec']:,.0f} events/s, decision "
                f"p50 {lat['p50_ms']}ms p99 {lat['p99_ms']}ms")

        if placement != "in-process":
            # The acceptance comparison: same workload, same shard
            # count, shards back in the router's process.
            with make_cluster(n_shards, _os.path.join(root, "inproc"),
                              placement="in-process") as cl:
                rep = run_steady(cl)
            in_process_comparison = {
                "n_shards": n_shards,
                "events_per_sec": rep["events_per_sec"],
                "batches_per_sec": rep["batches_per_sec"],
                "decision_p50_ms":
                    rep["decision_latency"]["p50_ms"],
                "decision_p99_ms":
                    rep["decision_latency"]["p99_ms"],
                "reconciles": rep["reconciles"],
            }
            log(f"serving comparison [in-process]: {n_shards} "
                f"shard(s) -> {rep['events_per_sec']:,.0f} events/s "
                f"(worker mode: {sweep[-1]['events_per_sec']:,.0f})")

        # ---- chaos phase (at n_shards): kill one shard AND, under
        # socket placement, partition another mid-stream ----
        kill_at = n_batches // 2
        partition_target = 1 if (placement == "sockets"
                                 and n_shards > 1) else None
        with make_cluster(n_shards, _os.path.join(root, "chaos"),
                          auto_recover=False) as cl:
            for b in batches[:warm]:
                cl.submit(b)
                cl.poll()
            cl.reset_metrics()
            serve_rounds(cl, batches[warm:warm + kill_at])
            events_before = sum(
                s["events_applied"]
                for s in cl.metrics.report(
                    cl.pending_by_shard, cl.health_by_shard)["shards"])
            cl.kill_shard(0, reason="bench: kill-one-shard MTTR")
            if partition_target is not None:
                # The compound failure: a REAL SIGKILL on shard 0 and a
                # severed TCP link on shard 1 in the same outage window
                # — the partitioned worker must redial + reattach +
                # resync while the dead one's slices shed.
                cl.partition_shard(partition_target)
            # poll() materializes every decision host-side (journal
            # append precedes the commit), so the region is synced.
            t_kill = _time.monotonic()  # rqlint: disable=RQ601
            # The outage window: surviving shards keep serving the
            # second half while fault domain 0 is down (its slices shed
            # with recorded seqs) and shard 1 heals its link.
            serve_rounds(cl, batches[warm + kill_at:])
            outage_s = max(_time.monotonic() - t_kill, 1e-9)
            events_during = sum(
                s["events_applied"]
                for s in cl.metrics.report(
                    cl.pending_by_shard, cl.health_by_shard)["shards"]
            ) - events_before
            # recover_shard + poll are host-synced the same way (journal
            # replay digest-asserts on host before the runtime returns).
            t0 = _time.monotonic()  # rqlint: disable=RQ601
            info = cl.recover_shard(0)
            mttr_recover_ms = (_time.monotonic() - t0) * 1e3
            # Retransmit everything past the recovered shard's position
            # (the source-retransmit contract); duplicates are absorbed
            # by the survivors, the recovered shard applies its backlog,
            # and any group-commit loss window heals the same way.
            final_seq = batches[-1].seq
            for attempt in range(8):
                missing = [b for b in batches
                           if int(b.seq) > cl.applied_seq]
                if not missing:
                    break  # re-checked BEFORE any sleep: the committed
                    # mttr_reconverge_ms carries no idle padding
                if attempt:
                    _time.sleep(0.2)
                serve_rounds(cl, missing)
            mttr_reconverge_ms = (_time.monotonic() - t0) * 1e3
            if cl.applied_seq != final_seq:
                raise RuntimeError(
                    f"cluster failed to reconverge: applied_seq="
                    f"{cl.applied_seq} != {final_seq}")
            rep = cl.metrics.report(cl.pending_by_shard,
                                    cl.health_by_shard)
            chaos = {
                "n_shards": n_shards,
                "killed_shard": 0,
                "partitioned_shard": partition_target,
                "outage_batches": n_batches - kill_at,
                "outage_s": round(outage_s, 6),
                "healthy_events_per_sec_during_outage": round(
                    events_during / outage_s, 1),
                "replayed_on_recovery": info.replayed,
                "lost_acked_seqs_in_window":
                    list(info.lost_acked_seqs),
                "reattaches": rep["reattaches"],
                "resyncs": rep["resyncs"],
                "lost_in_window": rep["lost_in_window"],
                "mttr_recover_ms": round(mttr_recover_ms, 3),
                "mttr_reconverge_ms": round(mttr_reconverge_ms, 3),
                "reconverged_seq": int(final_seq),
            }
            payload = cl.write_metrics(
                out_path or "SERVING_BENCH.json",
                extra={"bench": {
                    "placement": placement,
                    "warmup_batches_excluded": warm,
                    "events_per_batch": epb,
                    "round_size": round_size,
                    "before": before,
                    "sweep": sweep,
                    "in_process_comparison": in_process_comparison,
                    "kill_one_shard": chaos,
                }})
    finally:
        shutil.rmtree(root, ignore_errors=True)

    steady = sweep[-1]
    log(f"serving chaos [{placement}]: shard 0 of {n_shards} killed"
        + (f" + shard {partition_target} partitioned"
           if partition_target is not None else "")
        + f" for {chaos['outage_batches']} batches; survivors served "
        f"{chaos['healthy_events_per_sec_during_outage']:,.0f} events/s "
        f"during the outage (steady {steady['events_per_sec']:,.0f}); "
        f"recovery replayed {chaos['replayed_on_recovery']} batches in "
        f"{chaos['mttr_recover_ms']:.0f}ms, reconverged in "
        f"{chaos['mttr_reconverge_ms']:.0f}ms; reattaches="
        f"{chaos['reattaches']} resyncs={chaos['resyncs']}; "
        f"reconciles={payload['reconciles']}")
    return {
        "metric": f"sharded serving events/sec ({n_feeds} feeds, "
                  f"{n_shards} shards, {placement}, journaled "
                  f"group-commit, coalesce={SERVING_COALESCE}, "
                  f"~{epb} ev/batch)",
        "value": steady["events_per_sec"],
        "unit": "events/s",
        "vs_baseline": (round(
            steady["events_per_sec"]
            / (before.get("steady_events_per_sec")
               or before["events_per_sec"]), 2)
            if before and (before.get("steady_events_per_sec")
                           or before.get("events_per_sec"))
            else None),
        "placement": placement,
        "decision_p50_ms": steady["decision_p50_ms"],
        "decision_p99_ms": steady["decision_p99_ms"],
        "decision_p99_trimmed_ms": steady.get("decision_p99_trimmed_ms"),
        "decision_p99_window_median_ms":
            steady.get("decision_p99_window_median_ms"),
        "decision_max_ms": steady["decision_max_ms"],
        "warmup_batches_excluded": warm,
        "durability": payload["durability"],
        "before": before,
        "sweep": sweep,
        "in_process_comparison": in_process_comparison,
        "kill_one_shard": chaos,
        "reconciles": payload["reconciles"],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", type=int, nargs="*", default=[1, 2, 3, 4, 5])
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--serving", action="store_true",
                    help="run the steady-state serving micro-bench "
                         "(redqueen_tpu.serving) instead of the preset "
                         "configs; writes the enveloped "
                         "rq.serving.metrics/1 artifact (--serving-out)")
    ap.add_argument("--shards", type=int, default=0,
                    help="with --serving: run the sharded-cluster bench "
                         "instead (scaling sweep up to N fault domains "
                         "+ kill-one-shard MTTR); writes the enveloped "
                         "rq.serving.metrics/2 artifact (--serving-out)")
    ap.add_argument("--workers", action="store_true",
                    help="with --serving --shards N: place every shard "
                         "in its own subprocess worker (serving.worker) "
                         "— the sweep measures true parallel fsync/"
                         "apply, and the artifact carries the same-N "
                         "in-process comparison (--in-process is the "
                         "default placement)")
    ap.add_argument("--in-process", dest="workers", action="store_false",
                    help="with --serving --shards N: keep every shard "
                         "in this process (default)")
    ap.add_argument("--sockets", action="store_true",
                    help="with --serving --shards N: subprocess workers "
                         "over authenticated TCP (serving.transport) — "
                         "the cross-host placement; the chaos phase "
                         "kills one worker AND partitions another")
    ap.add_argument("--serving-out", default="SERVING_BENCH.json",
                    help="artifact path for --serving "
                         "(default: SERVING_BENCH.json)")
    ap.add_argument("--serving-trace-out", default=None,
                    help="with --serving (no --shards): path of the "
                         "rq.telemetry.trace/1 artifact from the traced "
                         "re-run (default: SERVING_TRACE.json beside "
                         "--serving-out); render with tools/rqtrace.py")
    ap.add_argument("--learn", action="store_true",
                    help="run the Hawkes-estimation micro-bench "
                         "(redqueen_tpu.learn): simulate->fit->recover "
                         "convergence numbers + the corpus-scale fit "
                         "through the native loader; writes the "
                         "enveloped rq.learn.bench/1 artifact "
                         "(--learn-out)")
    ap.add_argument("--learn-out", default="LEARN_BENCH.json",
                    help="artifact path for --learn "
                         "(default: LEARN_BENCH.json)")
    ap.add_argument("--profile", type=str, default=None,
                    help="directory for jax.profiler traces (TensorBoard)")
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--seeds", type=int, default=None,
                    help="sweep width; default: the preset's n_seeds "
                         "(64 for configs 1/5, else 4)")
    args = ap.parse_args()

    # Shared persistent compilation cache (one policy: _jax_cache.py at the
    # repo root, which the path insert above makes importable); must precede
    # the first jax import.
    import _jax_cache

    _jax_cache.enable_persistent_cache()

    import jax

    # Second call AFTER import jax: the env-var path alone does not cache
    # for THIS process in this JAX version (see _jax_cache docstring).
    _jax_cache.enable_persistent_cache()

    from redqueen_tpu import runtime

    if args.cpu or args.quick:
        jax.config.update("jax_platforms", "cpu")
    else:
        # The resilience runtime's backend guard: honors a
        # supervisor-imposed CPU degradation (RQ_BACKEND=cpu) and
        # otherwise runs the shared deadline-bounded liveness probe.
        runtime.ensure_backend(log=log)
    log(f"devices: {jax.devices()}")
    platform = jax.devices()[0].platform

    if args.learn:
        res = bench_learn(quick=args.quick, out_path=args.learn_out)
        res["platform"] = platform
        print(json.dumps(res))
        log(f"wrote {args.learn_out}")
        if args.out:
            runtime.atomic_write_json(args.out, [res], indent=2)
        return

    if args.serving:
        if (args.workers or args.sockets) and not args.shards:
            ap.error("--workers/--sockets need --serving --shards N "
                     "(worker placement is a cluster mode)")
        if args.workers and args.sockets:
            ap.error("--workers and --sockets are exclusive placements")
        if args.shards:
            res = bench_serving_cluster(
                args.shards, quick=args.quick,
                out_path=args.serving_out,
                placement=("sockets" if args.sockets
                           else "workers" if args.workers
                           else "in-process"))
        else:
            res = bench_serving(quick=args.quick,
                                out_path=args.serving_out,
                                trace_out_path=args.serving_trace_out)
        res["platform"] = platform
        print(json.dumps(res))
        log(f"wrote {args.serving_out}")
        if args.out:
            runtime.atomic_write_json(args.out, [res], indent=2)
        return

    results = []
    preempted = None
    with runtime.preemption_guard(log=log):
        for which in args.configs:
            try:
                runtime.check_preempt(f"config {which}")
            except runtime.PreemptedError as e:
                preempted = e
                break
            pdir = f"{args.profile}/config{which}" if args.profile else None
            out = bench_config(which, quick=args.quick,
                               profile_dir=pdir, n_seeds=args.seeds)
            # A CPU fallback (dead tunnel) must never pass as a TPU
            # artifact.
            out["platform"] = platform
            results.append(out)
            print(json.dumps(results[-1]))
            runtime.heartbeat()  # prove progress to a supervising process
            if args.out:
                # Incremental + atomic: a kill mid-sweep keeps every
                # completed config, and no reader ever sees a torn file.
                runtime.atomic_write_json(
                    args.out, {"partial": True, "results": results},
                    indent=2)
    if args.out and preempted is None:
        runtime.atomic_write_json(args.out, results, indent=2)
        log(f"wrote {args.out}")
    if preempted is not None:
        log(f"preempted: {preempted}; completed configs are in the "
            f"artifact — exiting")
        raise SystemExit(128 + (preempted.signum or 15))


if __name__ == "__main__":
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
